"""Serve ingress smoke for tools/check.sh: prove the multi-proxy front door
works end-to-end on a 2-node mini-cluster, fast (~60s).

Checks, in order:
  1. controller-managed fleet: `serve.start(proxy_location="EveryNode")`
     brings up one proxy per node, both route the app, and both appear in
     the head's service directory (serve_proxy_up);
  2. burst -> shed -> recover: a burst 4x past the per-app queue cap gets
     some fast `503 + Retry-After` (shed, counted in the proxy's stats) and
     ZERO hangs/5xx-other, then a single request succeeds again;
  3. graceful drain-on-stop: a replica scale-down under live load completes
     every admitted request (zero drops), and `drain_proxy` walks the wire
     serve_drain/serve_drained pair — the proxy sheds with "draining",
     leaves the directory, and its port stops answering.

Exit 0 on success; any assertion/exception fails the check stage.
"""

from __future__ import annotations

import os
import sys
import threading
import time
import urllib.error
import urllib.request

sys.path.insert(0, os.path.dirname(os.path.dirname(os.path.abspath(__file__))))
os.environ.setdefault("JAX_PLATFORMS", "cpu")


def _get(url, timeout=30):
    try:
        with urllib.request.urlopen(url, timeout=timeout) as r:
            return r.status, dict(r.headers)
    except urllib.error.HTTPError as e:
        e.read()
        return e.code, dict(e.headers)


def main() -> int:
    import ray_tpu
    from ray_tpu import serve
    from ray_tpu.cluster_utils import Cluster

    cluster = Cluster(head_node_args={"num_cpus": 2})
    try:
        cluster.add_node(num_cpus=2)

        @serve.deployment(
            num_replicas=2, max_concurrent_queries=2, max_queued_requests=4
        )
        def app(request):
            time.sleep(0.15)
            return "ok"

        serve.run(app.bind(), route_prefix="/app", _blocking_http=False)
        serve.start(proxy_location="EveryNode")
        # The controller's reconcile loop converges the fleet (a node that
        # raced the first ensure_proxies pass gets its proxy within ~2s).
        deadline = time.time() + 30
        ports = []
        while time.time() < deadline:
            ports = sorted(
                p for nid, p in serve.proxy_ports().items()
                if nid != "head" and p
            )
            if len(ports) == 2:
                break
            time.sleep(0.5)
        assert len(ports) == 2, f"expected one proxy per node: {ports}"
        for p in ports:
            status, _ = _get(f"http://127.0.0.1:{p}/app")
            assert status == 200, f"proxy on :{p} cannot route /app"
        from ray_tpu._private.worker import global_worker

        directory = global_worker.context.serve_directory()
        assert len(directory) >= 2, f"service directory: {directory}"
        print(f"[serve_smoke] 2-proxy fleet up on ports {ports}, "
              f"{len(directory)} directory entries")

        # ---- burst -> shed -> recover ---------------------------------
        target = ports[0]
        url = f"http://127.0.0.1:{target}/app"
        codes, lock = [], threading.Lock()

        def fire():
            t0 = time.monotonic()
            status, headers = _get(url)
            with lock:
                codes.append((status, time.monotonic() - t0, headers))

        burst = [threading.Thread(target=fire) for _ in range(16)]
        for t in burst:
            t.start()
        for t in burst:
            t.join()
        got = [c for c, _t, _h in codes]
        sheds = [(c, t, h) for c, t, h in codes if c == 503]
        assert got.count(200) >= 4, f"admitted window lost: {got}"
        assert sheds, f"burst 4x past the cap never shed: {got}"
        assert all(c in (200, 503) for c in got), f"unexpected codes: {got}"
        for _c, elapsed, headers in sheds:
            assert "Retry-After" in headers, "shed without Retry-After"
            assert elapsed < 1.0, f"slow shed ({elapsed:.2f}s)"
        status, _ = _get(url)
        assert status == 200, "no recovery after the burst"
        print(f"[serve_smoke] burst: {got.count(200)} ok / "
              f"{len(sheds)} fast sheds, recovered")

        # ---- graceful drain on replica stop --------------------------
        results, errors = [], []

        def call():
            try:
                status, _ = _get(url, timeout=60)
                results.append(status)
            except Exception as e:  # noqa: BLE001
                errors.append(repr(e))

        live = [threading.Thread(target=call) for _ in range(4)]
        for t in live:
            t.start()
        time.sleep(0.05)
        serve.run(  # scale 2 -> 1 mid-load: drain, don't drop
            app.options(num_replicas=1).bind(),
            route_prefix="/app", _blocking_http=False,
        )
        for t in live:
            t.join()
        assert not errors, f"admitted requests dropped in drain: {errors}"
        assert all(c in (200, 503) for c in results), results
        assert results.count(200) >= 1, results
        print(f"[serve_smoke] scale-down under load: {results} (no drops)")

        # ---- wire drain of one proxy ---------------------------------
        controller = serve.api._get_controller()
        proxies = ray_tpu.get(controller.get_proxies.remote())
        nid = sorted(proxies)[0]
        drained_port = proxies[nid]["port"]
        result = ray_tpu.get(
            controller.drain_proxy.remote(nid, 10.0), timeout=30
        )
        assert result["ok"], f"proxy drain failed: {result}"
        deadline = time.time() + 10
        while time.time() < deadline:
            directory = global_worker.context.serve_directory()
            if not any(e.get("port") == drained_port for e in directory):
                break
            time.sleep(0.2)
        else:
            raise AssertionError(f"drained proxy still listed: {directory}")
        survivor = [p for p in ports if p != drained_port][0]
        status, _ = _get(f"http://127.0.0.1:{survivor}/app")
        assert status == 200, "survivor proxy stopped serving after drain"
        print(f"[serve_smoke] proxy :{drained_port} drained off the wire; "
              f"survivor :{survivor} still serving")

        serve.shutdown()
        print("[serve_smoke] OK")
        return 0
    finally:
        cluster.shutdown()


if __name__ == "__main__":
    sys.exit(main())
