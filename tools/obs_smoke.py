"""Observability smoke for tools/check.sh: on a mini-cluster under load, the
time-series store must accumulate history (>=3 points on a counter-rate
series), cluster events must record the runtime's transitions, and the
default shed-rate alert must FIRE during a saturation burst and RESOLVE
after it. Fast (<~45s) and assertion-fatal — a broken over-time layer fails
the pre-merge gate before tier-1 runs."""

import os
import sys
import time

sys.path.insert(0, os.path.dirname(os.path.dirname(os.path.abspath(__file__))))


def main() -> int:
    import ray_tpu
    from ray_tpu import serve
    from ray_tpu.util import state

    ray_tpu.init(num_cpus=8, _system_config={
        "serve_replica_inflight_cap_factor": 2.0,
        "obs_series_step_s": 0.25,
        "alert_eval_interval_s": 0.25,
    })
    try:
        @ray_tpu.remote
        def nop():
            return None

        # --- series history: a counter-rate series gains points over time.
        ray_tpu.get([nop.remote() for _ in range(20)], timeout=60)
        time.sleep(1.2)  # first flush sets counter cursors
        t_mark = time.time()
        for _ in range(3):
            ray_tpu.get([nop.remote() for _ in range(20)], timeout=60)
            time.sleep(0.6)
        deadline = time.time() + 15
        points = []
        while time.time() < deadline:
            res = state.query_series(
                "ray_tpu_scheduler_tasks_dispatched_total",
                since=t_mark, step=0.5,
            )
            points = [p for s in res["series"] for p in s["points"]]
            if len(points) >= 3 and sum(v for _, v in points) > 0:
                break
            time.sleep(0.3)
        assert len(points) >= 3, f"series has {len(points)} point(s), need >=3"
        assert sum(v * res["step"] for _, v in points) >= 40, points
        print(f"series: dispatched-rate has {len(points)} points OK")

        # --- events: the runtime's own transitions are in the log.
        kinds = {e["kind"] for e in state.list_cluster_events()}
        assert "worker_started" in kinds, kinds
        print(f"events: {sorted(kinds)} recorded OK")

        # --- alerts: saturate Serve -> shed alert fires -> unload -> resolves.
        @serve.deployment(max_concurrent_queries=1)
        class Sleepy:
            def __call__(self, x):
                time.sleep(0.2)
                return x

        handle = serve.run(Sleepy.bind(), _blocking_http=False)
        from ray_tpu.serve._private.common import RequestShedded

        def alert_state():
            for a in state.list_alerts():
                if a["name"] == "serve_shed_rate":
                    return a["state"]
            return None

        responses, sheds = [], 0
        deadline = time.time() + 40
        while time.time() < deadline:
            try:
                responses.append(handle.remote(1))
            except RequestShedded:
                sheds += 1
            if sheds and sheds % 50 == 0 and alert_state() == "firing":
                break
            time.sleep(0.002)
        assert alert_state() == "firing", (
            f"shed alert never fired ({sheds} sheds)"
        )
        assert any(
            e["data"].get("rule") == "serve_shed_rate"
            for e in state.list_cluster_events(kind="alert_firing")
        )
        print(f"alerts: serve_shed_rate FIRING after {sheds} sheds OK")

        for r in responses:
            r.result(timeout=60)
        deadline = time.time() + 40
        while time.time() < deadline and alert_state() != "ok":
            time.sleep(0.5)
        assert alert_state() == "ok", "shed alert never resolved"
        assert any(
            e["data"].get("rule") == "serve_shed_rate"
            for e in state.list_cluster_events(kind="alert_resolved")
        )
        print("alerts: serve_shed_rate RESOLVED after the burst OK")
    finally:
        try:
            serve.shutdown()
        except Exception:
            pass
        ray_tpu.shutdown()
    print("OBS_SMOKE_OK")
    return 0


if __name__ == "__main__":
    sys.exit(main())
