#!/usr/bin/env bash
# One-stop pre-merge gate: rt-lint (static invariants) then the tier-1 test
# suite (ROADMAP.md "Tier-1 verify"). Usage: tools/check.sh [--lint-only]
set -euo pipefail
cd "$(dirname "$0")/.."

echo "== rt-lint (ray_tpu.devtools) =="
python -m ray_tpu.devtools.lint ray_tpu

echo
echo "== rt-verify (session machine + lock order + native C + stale binaries) =="
python -m ray_tpu.devtools.verify ray_tpu

if [[ "${1:-}" == "--lint-only" ]]; then
    exit 0
fi

echo
echo "== rt-verify explore (control-plane interleaving sweep + corpus replay) =="
timeout -k 10 180 env JAX_PLATFORMS=cpu \
    python -m ray_tpu.devtools.verify ray_tpu --passes stale --explore all

echo
echo "== native wire-codec parity fuzz (from-source build + C/py byte parity) =="
timeout -k 10 180 env JAX_PLATFORMS=cpu python tools/native_parity_fuzz.py

echo
echo "== wire decoder fuzz (structure-aware mutations, corpus replay, >=10k/codec) =="
timeout -k 10 300 env JAX_PLATFORMS=cpu python -m ray_tpu.devtools.verify ray_tpu --passes none --fuzz 12000

echo
echo "== sanitizer replay (ASan/UBSan rebuild + fuzz corpus + arena stress) =="
timeout -k 10 600 env JAX_PLATFORMS=cpu python tools/sanitize_native.py

echo
echo "== chaos smoke (seeded failpoint schedule) =="
timeout -k 10 180 env JAX_PLATFORMS=cpu python tools/chaos_smoke.py

echo
echo "== introspection smoke (stacks + memory + profile on a mini-cluster) =="
timeout -k 10 120 env JAX_PLATFORMS=cpu python tools/introspect_smoke.py

echo
echo "== data-plane smoke (peer-direct transfers, zero head relay) =="
timeout -k 10 120 env JAX_PLATFORMS=cpu python tools/dataplane_smoke.py

echo
echo "== serve ingress smoke (2-proxy fleet, burst->shed->recover, drain-on-stop) =="
timeout -k 10 180 env JAX_PLATFORMS=cpu python tools/serve_smoke.py

echo
echo "== observability smoke (series history, event log, shed alert fire->resolve) =="
timeout -k 10 180 env JAX_PLATFORMS=cpu python tools/obs_smoke.py

echo
echo "== jobs smoke (2-driver mini-cluster: attribution + job_starved fire->resolve) =="
timeout -k 10 180 env JAX_PLATFORMS=cpu python tools/jobs_smoke.py

echo
echo "== trace smoke (one Serve request traced proxy->router->replica->task, latency report) =="
timeout -k 10 180 env JAX_PLATFORMS=cpu python tools/trace_smoke.py

echo
echo "== train smoke (4-worker gang, seeded straggler named + alert fire->resolve, goodput ledger) =="
timeout -k 10 240 env JAX_PLATFORMS=cpu python tools/train_smoke.py

echo
echo "== elastic smoke (4-worker gang, seeded kill -> resize-in-place at world 3, bit-exact resume) =="
timeout -k 10 240 env JAX_PLATFORMS=cpu python tools/elastic_smoke.py

echo
echo "== tier-1 tests =="
rm -f /tmp/_t1.log
timeout -k 10 870 env JAX_PLATFORMS=cpu \
    python -m pytest tests/ -q -m 'not slow' --continue-on-collection-errors \
    -p no:cacheprovider -p no:xdist -p no:randomly 2>&1 | tee /tmp/_t1.log
rc=${PIPESTATUS[0]}
echo DOTS_PASSED=$(grep -aE '^[.FEsx]+( *\[ *[0-9]+%\])?$' /tmp/_t1.log | tr -cd . | wc -c)
exit $rc
