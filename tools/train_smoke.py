"""Training-observability smoke for tools/check.sh: a 4-worker gang with one
rank seeded slow (`train.step` delay failpoint armed programmatically on rank
1) must produce per-step phase series MID-RUN, fire the `train_straggler`
alert while the skew is sustained and RESOLVE it after the gang ends, and the
goodput ledger must name rank 1 + its dominant phase with >=95% wall-time
coverage. Fast (<~60s) and assertion-fatal — a broken step clock, skew fold,
or ledger fails the pre-merge gate before tier-1 runs."""

import os
import sys
import threading
import time

sys.path.insert(0, os.path.dirname(os.path.dirname(os.path.abspath(__file__))))

SLOW_RANK = 1
DELAY_S = 0.2
STEPS = 40


def train_fn(config):
    from ray_tpu._private import failpoints
    from ray_tpu.air import session

    if session.get_world_rank() == SLOW_RANK:
        # Programmatic, not env: the env schedule would reach every worker.
        failpoints.arm("train.step", "delay", DELAY_S, trigger="always")
    for step in range(STEPS):
        session.mark_phase("step_exec")
        time.sleep(0.005)
        session.report({"step": step})


def main() -> int:
    import ray_tpu
    from ray_tpu.train.data_parallel_trainer import DataParallelTrainer
    from ray_tpu.air import ScalingConfig
    from ray_tpu.util import state

    ray_tpu.init(num_cpus=8, _system_config={
        "train_straggler_skew_s": 0.05,
        "obs_series_step_s": 0.25,
        "alert_eval_interval_s": 0.25,
    })
    t_start = time.time()
    try:
        trainer = DataParallelTrainer(
            train_fn, scaling_config=ScalingConfig(num_workers=4)
        )
        box = {}

        def run():
            box["result"] = trainer.fit()

        fit = threading.Thread(target=run, daemon=True)
        fit.start()

        def alert_state():
            for a in state.list_alerts():
                if a["name"] == "train_straggler":
                    return a["state"]
            return None

        # --- mid-run: phase series exist (dead-worker series are pruned at
        # gang teardown, so this HAS to be observed while the gang is alive)
        # and the straggler alert fires on the sustained skew.
        fired = False
        phase_points = 0
        deadline = time.time() + 60
        while time.time() < deadline and fit.is_alive():
            if not phase_points:
                res = state.query_series(
                    "ray_tpu_train_step_seconds", since=t_start, step=0.5,
                )
                phase_points = sum(len(s["points"]) for s in res["series"])
            if not fired and alert_state() == "firing":
                fired = True
            if fired and phase_points:
                break
            time.sleep(0.25)
        assert phase_points > 0, "no ray_tpu_train_step_seconds points mid-run"
        print(f"series: {phase_points} train-step phase point(s) mid-run OK")
        assert fired, "train_straggler alert never fired during the run"
        assert any(
            e["data"].get("rule") == "train_straggler"
            for e in state.list_cluster_events(kind="alert_firing")
        )
        kinds = {e["kind"] for e in state.list_cluster_events()}
        assert "train_straggler" in kinds, kinds
        print("alerts: train_straggler FIRING on seeded skew OK")

        fit.join(timeout=120)
        assert not fit.is_alive(), "fit() did not finish"
        result = box.get("result")
        assert result is not None and result.error is None, result

        # --- gang ended: the executor parks the skew gauge at 0, the stale
        # window ages out, and the alert resolves.
        deadline = time.time() + 45
        while time.time() < deadline and alert_state() != "ok":
            time.sleep(0.5)
        assert alert_state() == "ok", "train_straggler alert never resolved"
        print("alerts: train_straggler RESOLVED after the gang ended OK")

        # --- goodput ledger: names the seeded rank + dominant phase, and the
        # buckets account (>=95% of) the gang's wall time.
        gangs = state.training_report()["gangs"]
        assert gangs, "training_report has no gangs"
        rep = next(iter(gangs.values()))
        assert rep["status"] == "done", rep["status"]
        straggler = rep["straggler"]
        assert straggler and straggler["rank"] == SLOW_RANK, straggler
        assert straggler.get("phase"), straggler
        assert rep["coverage"] >= 0.95, rep["coverage"]
        assert rep["steps"] >= STEPS - 1, rep["steps"]
        assert rep["buckets"]["productive"] > 0, rep["buckets"]
        shares = ", ".join(
            f"{b}={v / rep['wall_s'] * 100:.0f}%"
            for b, v in rep["buckets"].items() if v > 0
        )
        print(
            f"ledger: straggler rank {straggler['rank']} "
            f"({straggler['phase']}, slow in {straggler['slow_rounds']}/"
            f"{straggler['rounds']} rounds), coverage "
            f"{rep['coverage'] * 100:.1f}%, {shares} OK"
        )
    finally:
        ray_tpu.shutdown()
    print("TRAIN_SMOKE_OK")
    return 0


if __name__ == "__main__":
    sys.exit(main())
