"""Tracing smoke for tools/check.sh: on a mini-cluster, one Serve HTTP
request must yield ONE connected trace spanning proxy -> router -> replica
-> nested task, and state.latency_report() must attribute its wall time to
named components (non-empty, >=95% coverage). Fast (<~60s) and
assertion-fatal — a broken propagation seam fails the pre-merge gate
before tier-1 runs."""

import os
import sys
import time
import urllib.request

sys.path.insert(0, os.path.dirname(os.path.dirname(os.path.abspath(__file__))))


def main() -> int:
    import ray_tpu
    from ray_tpu import serve
    from ray_tpu.util import state, tracing

    ray_tpu.init(num_cpus=4, _system_config={"trace_sample_rate": 1.0})
    tracing.enable()
    try:
        @ray_tpu.remote
        def nested(x):
            return x * 2

        @serve.deployment
        class App:
            def __call__(self, req):
                return {"out": ray_tpu.get(nested.remote(21))}

        serve.run(App.bind(), route_prefix="/app")
        from ray_tpu._private.worker import global_worker

        port = global_worker.context.serve_directory()[0]["port"]
        resp = urllib.request.urlopen(f"http://127.0.0.1:{port}/app",
                                      timeout=30)
        assert resp.status == 200, resp.status
        assert b"42" in resp.read()

        deadline = time.time() + 20
        trace = None
        while time.time() < deadline:
            req_traces = [t for t in state.list_traces()
                          if t["root_kind"] == "request"]
            if req_traces:
                t = state.get_trace(req_traces[-1]["trace_id"])
                kinds = {s["kind"] for s in t["spans"]}
                if {"request", "router", "submit", "execute"} <= kinds and any(
                    "nested" in s["name"] for s in t["spans"]
                ):
                    trace = t
                    break
            time.sleep(0.3)
        assert trace is not None, "no connected request trace appeared"
        assert len({s["trace_id"] for s in trace["spans"]}) == 1
        span_ids = {s["span_id"] for s in trace["spans"]}
        for s in trace["spans"]:
            if s.get("parent_id"):
                assert s["parent_id"] in span_ids, s
        print(f"trace: {len(trace['spans'])} spans, one trace id, "
              f"parents linked OK")

        attr = trace["attribution"]
        assert attr["coverage"] >= 0.95, attr
        rep = state.latency_report()
        assert rep["traces"] >= 1 and rep["components"], rep
        assert rep["coverage"] >= 0.95, rep
        top = ", ".join(
            f"{k}={v['share'] * 100:.0f}%"
            for k, v in list(rep["components"].items())[:4]
        )
        print(f"latency_report: {rep['traces']} trace(s), "
              f"coverage {rep['coverage'] * 100:.1f}%, {top} OK")
        print("trace smoke OK")
        return 0
    finally:
        try:
            serve.shutdown()
        except Exception:
            pass
        ray_tpu.shutdown()


if __name__ == "__main__":
    sys.exit(main())
