#!/usr/bin/env python
"""Chaos smoke: a seeded failpoint schedule over the mini-cluster.

The fast pre-merge gate (tools/check.sh runs this between rt-lint and
tier-1): worker crashes, injected scheduler-handler faults, and object-loss
all recover (or surface typed errors) under one deterministic schedule.
The full failpoint x workload matrix lives in tests/test_failpoints.py —
this is the 30-second canary.
"""

from __future__ import annotations

import os
import sys

sys.path.insert(0, os.path.dirname(os.path.dirname(os.path.abspath(__file__))))

# Worker-side schedule rides the env so spawned workers inherit it: a seeded
# 6% chance each exec crashes after user code ran but before results stored.
os.environ["RAY_TPU_FAILPOINTS"] = "worker.crash_after_exec_end=crash@prob:0.06:7"

import numpy as np  # noqa: E402

import ray_tpu  # noqa: E402
from ray_tpu._private import failpoints  # noqa: E402


def main() -> int:
    # worker_pipeline_depth=1: a worker crash kills exactly the running task.
    # With deep pipelining a crash also wipes the window's BUFFERED dones
    # (completed work whose commit message died with the process), so a dense
    # crash schedule over instant tasks re-kills whole windows faster than
    # retries drain — real semantics the failpoint matrix covers separately
    # (tests/test_failpoints.py); the smoke wants convergence, not amplification.
    ray_tpu.init(num_cpus=2, _system_config={
        "use_native_object_arena": False,
        "worker_pipeline_depth": 1,
    })

    # --- 1) tasks survive seeded worker crashes -------------------------------
    @ray_tpu.remote(max_retries=8)
    def sq(i):
        return i * i

    out = ray_tpu.get([sq.remote(i) for i in range(24)], timeout=180)
    assert out == [i * i for i in range(24)], out
    print("chaos-smoke: seeded worker crashes recovered")

    # --- 2) lost segment under the driver reader -> lineage reconstruction ---
    @ray_tpu.remote(max_retries=8)
    def big():
        return np.arange(100_000)

    ref = big.remote()
    v1 = ray_tpu.get(ref, timeout=60)
    failpoints.arm("object.lose_segment", "lose")  # one-shot
    v2 = ray_tpu.get(ref, timeout=60)
    assert (v1 == v2).all()
    print("chaos-smoke: injected segment loss reconstructed, trace:",
          failpoints.trace())

    # --- 3) injected scheduler-handler crash surfaces typed, others proceed --
    failpoints.arm("sched.cmd.submit", "error", trigger="nth", nth=5)
    refs = [sq.remote(i) for i in range(10)]
    injected = ok = 0
    for r in refs:
        try:
            ray_tpu.get(r, timeout=60)
            ok += 1
        except failpoints.FailpointInjected:
            injected += 1
    assert injected == 2 and ok == 8, (injected, ok)
    print(f"chaos-smoke: sched.cmd.submit nth:5 -> {injected} typed "
          f"injections, {ok} completions")
    failpoints.reset()

    ray_tpu.shutdown()
    print("chaos-smoke: PASS")
    return 0


if __name__ == "__main__":
    sys.exit(main())
