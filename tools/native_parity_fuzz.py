#!/usr/bin/env python
"""Native-vs-Python wire-codec parity fuzz (tools/check.sh stage).

Forces a from-source rebuild of `_native/wire_native.c`, then round-trips a
randomized message for EVERY tag in MESSAGE_GRAMMAR (plus adversarial value
shapes) through both codecs, asserting:

  1. byte parity:     C.pack(msg) == PyCodec.pack(msg)
  2. cross-decode:    PyCodec.unpack(C.pack(msg)) == msg (and vice versa)
  3. dumps/loads:     serialization round-trips the framed form

Seeded (--seed, default 20260804) so a failure replays exactly.
"""

from __future__ import annotations

import argparse
import os
import random
import sys

REPO = os.path.dirname(os.path.dirname(os.path.abspath(__file__)))
sys.path.insert(0, REPO)


def rebuild_extension() -> None:
    """Delete the prebuilt .so and build from source — the stage must prove
    the CURRENT source compiles and loads on this toolchain."""
    from ray_tpu import _native

    if os.path.exists(_native._WIRE_LIB):
        os.unlink(_native._WIRE_LIB)
    mod = _native.load_wire_module()
    if mod is None:
        raise SystemExit(
            "native wire extension failed to build from source "
            "(g++/Python.h available? see _native/__init__.py)"
        )


def rand_simple(rng: random.Random, depth: int = 0):
    kinds = ["none", "bool", "int", "float", "bytes", "str"]
    if depth < 3:
        kinds += ["tuple", "list", "dict"]
    k = rng.choice(kinds)
    if k == "none":
        return None
    if k == "bool":
        return rng.random() < 0.5
    if k == "int":
        return rng.choice([
            0, 1, -1, rng.randint(-2**31, 2**31),
            rng.randint(-2**62, 2**62), 2**63 - 1, -(2**63),
            2**80,  # > i64: exercises the big-int hook escape
        ])
    if k == "float":
        return rng.choice([0.0, -1.5, 3.14159, 1e300, -1e-300])
    if k == "bytes":
        return rng.randbytes(rng.randint(0, 64))
    if k == "str":
        return "".join(
            rng.choice("abcé中 xyz_") for _ in range(rng.randint(0, 24))
        )
    if k == "tuple":
        return tuple(rand_simple(rng, depth + 1) for _ in range(rng.randint(0, 4)))
    if k == "list":
        return [rand_simple(rng, depth + 1) for _ in range(rng.randint(0, 4))]
    return {
        rng.choice(["a", "bb", "c" * 3, 7, b"k"]): rand_simple(rng, depth + 1)
        for _ in range(rng.randint(0, 4))
    }


def rand_meta(rng: random.Random):
    from ray_tpu._private.ids import ObjectID
    from ray_tpu._private.object_store import ObjectMeta

    oid = ObjectID(rng.randbytes(28))
    if rng.random() < 0.5:
        return ObjectMeta(
            object_id=oid, size=rng.randint(0, 1 << 20),
            inband=rng.randbytes(rng.randint(0, 128)),
            inline_buffers=[rng.randbytes(8) for _ in range(rng.randint(0, 2))],
            is_error=rng.random() < 0.1,
        )
    return ObjectMeta(
        object_id=oid, size=rng.randint(0, 1 << 30),
        segment=f"/dev/shm/seg_{rng.randint(0, 999)}",
        buffer_layout=[(0, 8), (8, rng.randint(1, 99))],
        node_id=rng.randbytes(16),
        arena_offset=rng.choice([None, rng.randint(0, 1 << 30)]),
        spilled=rng.random() < 0.2,
    )


def rand_spec(rng: random.Random):
    from ray_tpu._private.ids import ActorID, JobID, TaskID
    from ray_tpu._private.protocol import FunctionDescriptor, TaskSpec

    tid = TaskID.for_task(ActorID(b"\x00" * 12 + JobID.from_int(1).binary()))
    return TaskSpec(
        task_id=tid,
        func=FunctionDescriptor(rng.randbytes(8).hex(), "fuzz_fn"),
        num_returns=rng.randint(0, 3),
        resources={"CPU": float(rng.randint(0, 4))},
        max_retries=rng.randint(0, 3),
        name="fuzz", env_vars={"K": "v"} if rng.random() < 0.3 else {},
    )


def rand_exec(rng: random.Random):
    from ray_tpu._private.ids import ObjectID
    from ray_tpu._private.protocol import ExecRequest

    spec = rand_spec(rng)
    return ExecRequest(
        spec=spec,
        arg_metas=[rand_meta(rng) for _ in range(rng.randint(0, 2))],
        kwarg_metas={"k": rand_meta(rng)} if rng.random() < 0.3 else {},
        func_blob=rng.randbytes(32) if rng.random() < 0.3 else None,
        return_ids=[ObjectID(rng.randbytes(28)) for _ in range(spec.num_returns)],
    )


def rand_record(rng: random.Random):
    from ray_tpu._private.ids import ObjectID
    from ray_tpu._private.scheduler import fast_task_record

    spec = rand_spec(rng)
    return fast_task_record(
        spec,
        [("id", rng.randbytes(28)), ("meta", rand_meta(rng))],
        {"kw": ("id", rng.randbytes(28))},
        [ObjectID(rng.randbytes(28))],
        rng.randbytes(16) if rng.random() < 0.3 else None,
        rng.randint(0, 3),
    )


def message_for_tag(tag: str, rng: random.Random):
    """A randomized, arity-correct message for each grammar tag."""
    from ray_tpu._private.protocol import MESSAGE_GRAMMAR

    tid = rng.randbytes(24)
    special = {
        "done": lambda: ("done", tid, rng.random() < 0.9,
                         [rand_meta(rng) for _ in range(rng.randint(0, 2))],
                         {"exec_start": rng.random(), "exec_end": rng.random()}),
        "exec": lambda: ("exec", rand_exec(rng)),
        "cmd": lambda: ("cmd", "submit", rand_record(rng)),
        "req": lambda: ("req", rng.randint(0, 1 << 30), "get_metas",
                        [rng.randbytes(28)]),
        "resp": lambda: ("resp", rng.randint(0, 1 << 30), True,
                         [rand_meta(rng)]),
        "ref_ops": lambda: ("ref_ops", [
            (rng.choice(["add", "rel", "genrel", "srel"]), rng.randbytes(28))
            for _ in range(rng.randint(0, 8))
        ]),
        "own_meta": lambda: ("own_meta", rand_meta(rng)),
        "stream": lambda: ("stream", tid, rng.randint(0, 100), rand_meta(rng)),
        "batch": lambda: ("batch", [
            ("done", rng.randbytes(24), True, [rand_meta(rng)], None)
            for _ in range(rng.randint(1, 5))
        ]),
        "object_locations": lambda: ("object_locations", rng.randint(0, 99), {
            rng.randbytes(28): (rand_meta(rng),
                                [(rng.randbytes(16), "127.0.0.1:1")]),
        }),
    }
    if tag in special:
        return special[tag]()
    lo, hi = MESSAGE_GRAMMAR[tag]["arity"]
    n = rng.randint(lo, hi)
    return (tag,) + tuple(rand_simple(rng) for _ in range(n - 1))


def norm(x):
    """Structural normal form for equality across dataclass instances."""
    from ray_tpu._private.object_store import ObjectMeta
    from ray_tpu._private.protocol import ExecRequest, FunctionDescriptor, TaskSpec
    from ray_tpu._private.scheduler import TaskRecord

    if isinstance(x, TaskRecord):
        return ("REC", norm(x.spec), norm(list(x.arg_entries)),
                norm(x.kwarg_entries), norm(x.return_ids), x.func_blob,
                x.retries_left)
    if isinstance(x, ExecRequest):
        return ("EXEC", norm(x.spec), norm(x.arg_metas), norm(x.kwarg_metas),
                x.func_blob, norm(x.return_ids))
    if isinstance(x, (TaskSpec, ObjectMeta)):
        return tuple(sorted((k, norm(v)) for k, v in x.__dict__.items()))
    if isinstance(x, FunctionDescriptor):
        return (x.function_id, x.name)
    if isinstance(x, tuple):
        return tuple(norm(i) for i in x)
    if isinstance(x, list):
        return ("L",) + tuple(norm(i) for i in x)
    if isinstance(x, dict):
        pairs = [(repr(norm(k)), norm(v)) for k, v in x.items()]
        pairs.sort(key=lambda kv: kv[0])
        return ("D",) + tuple(pairs)
    if hasattr(x, "_binary"):
        return (type(x).__name__, x._binary)
    return x


def main() -> int:
    parser = argparse.ArgumentParser(description=__doc__)
    parser.add_argument("--seed", type=int, default=20260804)
    parser.add_argument("--rounds", type=int, default=40,
                        help="randomized messages per grammar tag")
    ns = parser.parse_args()

    rebuild_extension()
    from ray_tpu._private import serialization, wire
    from ray_tpu._private.protocol import MESSAGE_GRAMMAR

    native = wire._load_codec()
    assert wire.native_available(), "C codec must be active after rebuild"
    py = wire._PyCodec

    rng = random.Random(ns.seed)
    checked = 0
    for tag in sorted(MESSAGE_GRAMMAR):
        for _ in range(ns.rounds):
            msg = message_for_tag(tag, rng)
            c_bytes = native.pack(msg)
            p_bytes = py.pack(msg)
            assert c_bytes == p_bytes, (
                f"byte divergence for tag {tag!r}: "
                f"C={c_bytes[:60]!r} PY={p_bytes[:60]!r}"
            )
            via_c = native.unpack(p_bytes)
            via_py = py.unpack(c_bytes)
            want = norm(msg)
            assert norm(via_c) == want, f"C decode mismatch for {tag!r}"
            assert norm(via_py) == want, f"Python decode mismatch for {tag!r}"
            # Framed end-to-end through serialization (magic dispatch).
            framed = wire.encode(msg)
            assert framed is not None and framed[:1] == wire.MAGIC
            assert norm(serialization.loads(framed)) == want
            checked += 1
    # Adversarial simple-value structures (no tag constraint).
    for _ in range(600):
        val = ("cmd", "kv", rand_simple(rng))
        c_bytes = native.pack(val)
        assert c_bytes == py.pack(val), f"byte divergence for {val!r}"
        assert norm(py.unpack(c_bytes)) == norm(val)
        assert norm(native.unpack(c_bytes)) == norm(val)
        checked += 1
    print(f"native parity fuzz OK: {checked} messages, seed {ns.seed}, "
          f"{len(MESSAGE_GRAMMAR)} grammar tags")
    return 0


if __name__ == "__main__":
    sys.exit(main())
