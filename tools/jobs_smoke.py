"""Job-accounting smoke for tools/check.sh: on a 2-driver mini-cluster
(spawned head + two TCP client drivers), the head's JobLedger must attribute
each driver's disjoint workload to its own job exactly, the per-job sums
must reconcile with the known workload sizes, and the `job_starved` alert
must FIRE under a greedy-vs-light driver mix and RESOLVE once the greedy
tenant leaves. Fast (<~90s) and assertion-fatal — a broken attribution
layer fails the pre-merge gate before tier-1 runs."""

import os
import subprocess
import sys
import time

REPO = os.path.dirname(os.path.dirname(os.path.abspath(__file__)))
sys.path.insert(0, REPO)

N_A, N_B = 30, 12


def _client(address, authkey_hex, body):
    env = dict(os.environ, RAY_TPU_AUTHKEY_HEX=authkey_hex)
    env["PYTHONPATH"] = REPO + os.pathsep + env.get("PYTHONPATH", "")
    script = (
        "import sys; sys.path.insert(0, %r)\n"
        "import ray_tpu\n"
        "ray_tpu.init(address=%r)\n"
        "from ray_tpu._private.worker import global_worker\n"
        "print('JOB', global_worker.job_id.hex(), flush=True)\n"
        % (REPO, address)
    ) + body
    return subprocess.Popen(
        [sys.executable, "-c", script], env=env,
        stdout=subprocess.PIPE, stderr=subprocess.STDOUT, text=True,
    )


def _job_of(stdout: str) -> str:
    for line in stdout.splitlines():
        if line.startswith("JOB "):
            return line.split()[1]
    raise AssertionError(f"no JOB line in:\n{stdout}")


def main() -> int:
    # Head knobs ride the env into the spawned process: fast obs cadence, a
    # low starvation bar, and depth-1 pipelining so contention is PENDING
    # time (what the ledger meters), not worker-pipeline residency.
    os.environ["RAY_TPU_obs_series_step_s"] = "0.25"
    os.environ["RAY_TPU_alert_eval_interval_s"] = "0.25"
    os.environ["RAY_TPU_job_starved_wait_s"] = "0.5"
    os.environ["RAY_TPU_worker_pipeline_depth"] = "1"

    from ray_tpu._private.launch import spawn_head

    proc, info = spawn_head(num_cpus=2, num_tpus=0, timeout_s=60)
    os.environ["RAY_TPU_AUTHKEY_HEX"] = info["authkey_hex"]
    import ray_tpu
    from ray_tpu.util import state

    greedy = None
    try:
        # --- attribution: two client drivers, disjoint workloads.
        pa = _client(info["address"], info["authkey_hex"], f"""
@ray_tpu.remote
def fa(i):
    return i * 2
assert ray_tpu.get([fa.remote(i) for i in range({N_A})]) == [
    2 * i for i in range({N_A})]
print("DONE A")
""")
        pb = _client(info["address"], info["authkey_hex"], f"""
@ray_tpu.remote
def fb(i):
    return i + 1
assert ray_tpu.get([fb.remote(i) for i in range({N_B})]) == [
    i + 1 for i in range({N_B})]
print("DONE B")
""")
        out_a, _ = pa.communicate(timeout=120)
        out_b, _ = pb.communicate(timeout=120)
        assert pa.returncode == 0, out_a
        assert pb.returncode == 0, out_b
        job_a, job_b = _job_of(out_a), _job_of(out_b)
        assert job_a != job_b

        ray_tpu.init(address=info["address"])
        deadline = time.time() + 30
        ledger = {}
        while time.time() < deadline:
            ledger = {j["job"]: j for j in state.list_jobs()
                      if j["state"] == "FINISHED"}
            if {job_a, job_b} <= set(ledger):
                break
            time.sleep(0.25)
        assert {job_a, job_b} <= set(ledger), sorted(ledger)
        ta = ledger[job_a]["totals"]
        tb = ledger[job_b]["totals"]
        assert ta["tasks"]["submitted"] == N_A, ta
        assert ta["tasks"]["finished"] == N_A, ta
        assert tb["tasks"]["submitted"] == N_B, tb
        assert tb["tasks"]["finished"] == N_B, tb
        assert ta["cpu_seconds"] > 0 and tb["cpu_seconds"] > 0
        total = sum(j["totals"]["tasks"]["submitted"]
                    for j in state.list_jobs())
        assert total == N_A + N_B, total
        print(f"attribution: {job_a}={N_A} tasks, {job_b}={N_B} tasks, "
              f"sum reconciles OK")

        # --- starvation: greedy client floods the 2 CPUs; this (light)
        # driver's short tasks queue behind it -> job_starved fires.
        greedy = _client(info["address"], info["authkey_hex"], """
import time
@ray_tpu.remote
def hog():
    time.sleep(0.6)
deadline = time.time() + 12
inflight = []
while time.time() < deadline:
    while len(inflight) < 6:
        inflight.append(hog.remote())
    done, inflight = inflight[:1], inflight[1:]
    ray_tpu.get(done)
print("GREEDY DONE", flush=True)
""")

        @ray_tpu.remote
        def light():
            return 1

        def alert_state():
            for a in state.list_alerts():
                if a["name"] == "job_starved":
                    return a["state"]
            return None

        fired = False
        deadline = time.time() + 45
        while time.time() < deadline:
            ray_tpu.get(light.remote(), timeout=60)
            if alert_state() == "firing":
                fired = True
                break
            time.sleep(0.1)
        assert fired, "job_starved never fired under the greedy flood"
        assert any(
            e["data"].get("rule") == "job_starved"
            for e in state.list_cluster_events(kind="alert_firing")
        )
        print("alerts: job_starved FIRING under greedy-vs-light mix OK")

        greedy.communicate(timeout=60)
        deadline = time.time() + 45
        while time.time() < deadline and alert_state() != "ok":
            ray_tpu.get(light.remote(), timeout=60)
            time.sleep(0.5)
        assert alert_state() == "ok", "job_starved never resolved"
        assert any(
            e["data"].get("rule") == "job_starved"
            for e in state.list_cluster_events(kind="alert_resolved")
        )
        print("alerts: job_starved RESOLVED after the greedy driver left OK")
    finally:
        try:
            ray_tpu.shutdown()
        except Exception:
            pass
        if greedy is not None and greedy.poll() is None:
            greedy.kill()
        proc.terminate()
        proc.wait(timeout=30)
    print("JOBS_SMOKE_OK")
    return 0


if __name__ == "__main__":
    sys.exit(main())
