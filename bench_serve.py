"""Serve ingress benchmark: sustained open-loop load against the HTTP front
door, saturation behavior (shed-not-collapse), and multi-proxy scaling.

Builds a 2-node virtual cluster with the controller-managed per-node proxy
fleet (serve.start(proxy_location="EveryNode")) and records:

  - ``serve_capacity_rps``       — closed-loop single-proxy capacity (the
    reference point the saturation phase is sized from);
  - ``serve_sustained_rps`` + ``serve_p50_ms/p95/p99`` — open-loop load at
    ~70% of capacity: the steady-state latency distribution a production
    front door is judged on;
  - ``serve_saturation_goodput_ratio`` — goodput (200s/s) at 2x-capacity
    offered load over single-proxy capacity. Admission control must convert
    the overload into fast 503s, not latency collapse: the acceptance floor
    is >= 0.8;
  - ``serve_shed_latency_ms``    — mean wall time of a shed 503 (+
    Retry-After) during saturation: shedding is only useful if it is fast;
  - ``serve_p99_admitted_ms``    — p99 of ADMITTED requests under 2x load:
    bounded by the per-app queue cap, not the offered load;
  - ``serve_2proxy_aggregate_rps`` / ``serve_proxy_scaling_ratio`` —
    closed-loop aggregate across BOTH node proxies over single-proxy
    capacity (floor >= 1.5: adding a node must add ingress bandwidth).

Prints one human-readable line plus one JSON line per metric, same format
as bench_core.py; pipe to BENCH_SERVE.json and check with
``python bench_check.py BENCH_SERVE.json --baseline BENCH_SERVE.json``.
"""

from __future__ import annotations

import http.client
import json
import threading
import time

CAPACITY_PROBE_S = 4.0
SUSTAINED_S = 8.0
SATURATION_S = 8.0
# Threads per CLIENT PROCESS in closed-loop phases. One dedicated process
# per proxy: a single client interpreter's GIL would cap the aggregate
# 2-proxy measurement at roughly the single-proxy number and hide the
# scaling the phase exists to measure.
CLOSED_LOOP_THREADS = 16
MIN_OPEN_LOOP_THREADS = 64
MAX_OPEN_LOOP_THREADS = 160


def _emit(results, name, value, unit):
    rec = {"metric": name, "value": round(value, 3), "unit": unit}
    results.append(rec)
    print(json.dumps(rec), flush=True)


def _percentile(sorted_vals, q):
    if not sorted_vals:
        return 0.0
    return sorted_vals[min(len(sorted_vals) - 1, int(q * len(sorted_vals)))]


class _Conn:
    """Keep-alive HTTP client bound to one proxy port."""

    def __init__(self, port):
        self.port = port
        self.conn = None

    def get(self, path, timeout=30):
        if self.conn is None:
            self.conn = http.client.HTTPConnection(
                "127.0.0.1", self.port, timeout=timeout
            )
        try:
            self.conn.request("GET", path)
            resp = self.conn.getresponse()
            resp.read()
            return resp.status
        except Exception:
            try:
                self.conn.close()
            except Exception:
                pass
            self.conn = None
            raise


def _hammer(port, duration_s, n_threads, path, out_q):
    """Closed-loop worker body, run in a DEDICATED client process per proxy
    (spawned, not forked: a fresh interpreter whose GIL is all ours)."""
    stop = threading.Event()
    counts = []
    lock = threading.Lock()

    def worker():
        conn = _Conn(port)
        n = 0
        while not stop.is_set():
            try:
                if conn.get(path) == 200:
                    n += 1
            except Exception:
                time.sleep(0.01)
        with lock:
            counts.append(n)

    threads = [threading.Thread(target=worker) for _ in range(n_threads)]
    t0 = time.monotonic()
    for t in threads:
        t.start()
    time.sleep(duration_s)
    stop.set()
    for t in threads:
        t.join()
    out_q.put(sum(counts) / (time.monotonic() - t0))


def _closed_loop(ports, duration_s, path="/infer"):
    """One client process per proxy port, CLOSED_LOOP_THREADS each, in a
    tight request loop. Returns aggregate achieved RPS."""
    import multiprocessing as mp

    ctx = mp.get_context("spawn")
    q = ctx.Queue()
    procs = [
        ctx.Process(
            target=_hammer,
            args=(port, duration_s, CLOSED_LOOP_THREADS, path, q),
        )
        for port in ports
    ]
    for p in procs:
        p.start()
    total = sum(q.get(timeout=duration_s + 60) for _ in procs)
    for p in procs:
        p.join()
    return total


def _open_loop(port, rate_rps, duration_s, path="/infer"):
    """Fire requests on a fixed schedule (open loop: arrivals don't wait for
    completions), spread over enough worker threads that blocked admitted
    requests can't silently throttle the offered load. Arrivals a worker
    cannot make by the wall deadline are dropped, not deferred — deferring
    would stretch the measurement window and understate the offered rate.
    Returns (ok_latencies, shed_latencies, errors, elapsed)."""
    n_threads = max(
        MIN_OPEN_LOOP_THREADS,
        min(MAX_OPEN_LOOP_THREADS, int(rate_rps / 8)),
    )
    ok, shed, errors = [], [], [0]
    lock = threading.Lock()
    per_thread_rate = rate_rps / n_threads
    interval = 1.0 / per_thread_rate if per_thread_rate > 0 else 1.0
    start = time.monotonic() + 0.2
    deadline = start + duration_s

    def worker(idx):
        conn = _Conn(port)
        # Stagger thread phases so arrivals approximate a uniform process.
        next_t = start + (idx / n_threads) * interval
        my_ok, my_shed = [], []
        while True:
            now = time.monotonic()
            if now >= deadline:
                break
            if now < next_t:
                time.sleep(next_t - now)
            t0 = time.monotonic()
            try:
                status = conn.get(path)
                dt = time.monotonic() - t0
                if status == 200:
                    my_ok.append(dt)
                elif status == 503:
                    my_shed.append(dt)
                else:
                    with lock:
                        errors[0] += 1
            except Exception:
                with lock:
                    errors[0] += 1
            next_t += interval
            # Fell behind the schedule (blocked on admitted requests):
            # skip the missed arrivals rather than burst-firing the backlog.
            now = time.monotonic()
            if next_t < now:
                next_t = now
        with lock:
            ok.extend(my_ok)
            shed.extend(my_shed)

    threads = [
        threading.Thread(target=worker, args=(i,)) for i in range(n_threads)
    ]
    for t in threads:
        t.start()
    for t in threads:
        t.join()
    elapsed = time.monotonic() - start
    return ok, shed, errors[0], elapsed


def main():
    import ray_tpu
    from ray_tpu import serve
    from ray_tpu.cluster_utils import Cluster

    results = []
    cluster = Cluster(head_node_args={"num_cpus": 4})
    try:
        cluster.add_node(num_cpus=4)

        # Model-inference-shaped handler: ~25ms of LATENCY (not CPU). A
        # single proxy's bounded request pipeline (event loop + executor)
        # caps how many of these it can have in flight, so single-proxy
        # capacity is a per-proxy resource and the 2-proxy phase measures
        # real ingress scaling even on small hosts; replica capacity
        # (4 x 8 concurrent) sits far above one proxy's share.
        @serve.deployment(
            num_replicas=4,
            max_concurrent_queries=8,
            max_queued_requests=32,
        )
        def infer(request):
            time.sleep(0.025)
            return "ok"

        serve.run(infer.bind(), route_prefix="/infer", _blocking_http=False)
        serve.start(proxy_location="EveryNode")
        # The controller's reconcile loop converges the fleet; wait for it.
        deadline = time.monotonic() + 30
        ports = []
        while time.monotonic() < deadline:
            ports = sorted(
                p for nid, p in serve.proxy_ports().items()
                if nid != "head" and p
            )
            if len(ports) == 2:
                break
            time.sleep(0.5)
        assert len(ports) == 2, f"expected 2 node proxies, got {ports}"
        one = ports[0]

        # Warmup (routing tables, handles, replica pools).
        _closed_loop([one], 1.0)

        # 1. Single-proxy capacity (closed loop).
        capacity = _closed_loop([one], CAPACITY_PROBE_S)
        _emit(results, "serve_capacity_rps", capacity, "req/s")

        # 2. Sustained open-loop at ~70% capacity: steady-state latency.
        ok, shed_lat, errors, elapsed = _open_loop(
            one, 0.7 * capacity, SUSTAINED_S
        )
        ok.sort()
        _emit(results, "serve_sustained_rps", len(ok) / elapsed, "req/s")
        _emit(results, "serve_p50_ms", _percentile(ok, 0.50) * 1e3, "ms")
        _emit(results, "serve_p95_ms", _percentile(ok, 0.95) * 1e3, "ms")
        _emit(results, "serve_p99_ms", _percentile(ok, 0.99) * 1e3, "ms")
        print(f"# sustained: {len(ok)} ok, {len(shed_lat)} shed, "
              f"{errors} errors over {elapsed:.1f}s", flush=True)

        # 3. Saturation: 2x capacity offered. Goodput must hold (>= 0.8x
        # capacity), the overflow must shed FAST, and the p99 of admitted
        # requests stays bounded by the queue cap — not the offered load.
        ok2, shed2, errors2, elapsed2 = _open_loop(
            one, 2.0 * capacity, SATURATION_S
        )
        ok2.sort()
        goodput = len(ok2) / elapsed2
        _emit(results, "serve_saturation_goodput_ratio",
              goodput / capacity if capacity else 0.0, "ratio")
        _emit(results, "serve_saturation_shed_rps",
              len(shed2) / elapsed2, "req/s")
        _emit(results, "serve_shed_latency_ms",
              (sum(shed2) / len(shed2) * 1e3) if shed2 else 0.0, "ms")
        _emit(results, "serve_p99_admitted_ms",
              _percentile(ok2, 0.99) * 1e3, "ms")
        print(f"# saturation: {len(ok2)} ok, {len(shed2)} shed, "
              f"{errors2} errors over {elapsed2:.1f}s", flush=True)

        # 4. Two proxies: aggregate closed-loop RPS across both front doors.
        aggregate = _closed_loop(ports, CAPACITY_PROBE_S)
        _emit(results, "serve_2proxy_aggregate_rps", aggregate, "req/s")
        _emit(results, "serve_proxy_scaling_ratio",
              aggregate / capacity if capacity else 0.0, "ratio")

        serve.shutdown()
    finally:
        cluster.shutdown()

    print()
    for r in results:
        print(f"# {r['metric']:38s} {r['value']:>12g} {r['unit']}")
    return results


if __name__ == "__main__":
    main()
