"""Headline benchmark: GPT-2 small training throughput/MFU THROUGH the framework.

Runs the workload twice on the local TPU chip:
  1. via ``JaxTrainer.fit()`` — a real 1-worker gang (worker actor, backend
     bring-up, session reporting): the number the framework is judged on;
  2. the identical bare-jax step loop in a clean subprocess: the native
     baseline, mirroring the reference's Ray-vs-native parity method
     (`doc/source/ray-air/benchmarks.rst:178-212` — framework overhead over
     native DDP must be within noise).

Prints ONE JSON line {"metric", "value", "unit", "vs_baseline"} (plus
diagnostic fields):
 - value: tokens/sec/chip for GPT-2 small (124M), batch 16 x seq 1024,
   measured THROUGH JaxTrainer.
 - vs_baseline: measured MFU / 0.40 — BASELINE.json north star is >=40% MFU.
 - overhead_pct: (bare - framework) / bare * 100, the parity diagnostic.

Timing note: through the axon relay, block_until_ready does not synchronize,
so a scalar fetch after a pipelined window of steps forces the sync (fetch RTT
is amortized over the window).
"""

from __future__ import annotations

import json
import os
import subprocess
import sys

B, S, WARMUP, ITERS, WINDOWS = 16, 1024, 5, 30, 2


def _timed_tokens_per_sec():
    """Build GPT-2 small on a DP mesh over all local devices, run the
    warmup+timed step loop, and return (tokens_per_sec_total, n_devices).

    This exact function body is the workload for BOTH the framework run
    (inside the Train worker) and the bare-jax subprocess, so the comparison
    isolates framework overhead from model/compile differences.
    """
    import time

    import jax
    import numpy as np

    from ray_tpu.models import (
        GPTConfig,
        create_train_state,
        default_optimizer,
        make_train_step,
        shard_batch,
    )
    from ray_tpu.parallel import MeshSpec

    cfg = GPTConfig.gpt2_small()
    devices = jax.devices()
    mesh = MeshSpec(data=len(devices)).build(devices)
    opt = default_optimizer(learning_rate=3e-4)
    state = create_train_state(cfg, jax.random.PRNGKey(0), opt, mesh=mesh)
    step = make_train_step(cfg, opt, mesh=mesh)

    rng = np.random.default_rng(0)
    batch = shard_batch(
        {"tokens": rng.integers(0, cfg.vocab_size - 1, (B, S + 1)).astype(np.int32)},
        mesh,
    )
    for _ in range(WARMUP):
        state, m = step(state, batch)
    _ = float(m["loss"])  # sync
    # Best of N windows: the relay/link adds per-window jitter that a single
    # window folds into the headline number.
    best_dt = None
    for _ in range(WINDOWS):
        t0 = time.time()
        for _ in range(ITERS):
            state, m = step(state, batch)
        _ = float(m["loss"])  # sync
        dt = (time.time() - t0) / ITERS
        best_dt = dt if best_dt is None else min(best_dt, dt)
    return B * S / best_dt, len(devices)


def _train_loop(config):
    """The JaxTrainer per-worker loop: run the workload, report throughput."""
    from ray_tpu.air import session

    tps, n = _timed_tokens_per_sec()
    session.report({"tokens_per_sec": tps, "n_devices": n})


def _framework_run():
    """tokens/s + device count measured through JaxTrainer.fit()."""
    import ray_tpu
    from ray_tpu.air import RunConfig, ScalingConfig
    from ray_tpu.train.jax import JaxTrainer

    ray_tpu.init()
    try:
        trainer = JaxTrainer(
            _train_loop,
            scaling_config=ScalingConfig(num_workers=1, use_tpu=True),
            run_config=RunConfig(name="bench_gpt2"),
        )
        result = trainer.fit()
        if result.error is not None:
            raise result.error
        return result.metrics["tokens_per_sec"], int(result.metrics["n_devices"])
    finally:
        ray_tpu.shutdown()


def _bare_run():
    """The same workload in a clean subprocess (no framework on the path)."""
    proc = subprocess.run(
        [sys.executable, os.path.abspath(__file__), "--bare"],
        stdout=subprocess.PIPE,
        text=True,
        cwd=os.path.dirname(os.path.abspath(__file__)),
    )
    if proc.returncode != 0:
        raise RuntimeError(f"bare baseline subprocess failed rc={proc.returncode}")
    out = json.loads(proc.stdout.strip().splitlines()[-1])
    return out["tokens_per_sec"], out["n_devices"]


def main() -> None:
    from ray_tpu.models import GPTConfig, train_flops_per_token

    # v5e bf16 peak; override for other generations via env.
    peak_flops = float(os.environ.get("RAY_TPU_PEAK_FLOPS", 197e12))

    fw_tps, n_dev = _framework_run()
    try:
        bare_tps, _ = _bare_run()
    except Exception as e:
        # Parity diagnostic unavailable; the headline number is still valid.
        print(f"bare baseline failed: {e!r}", file=sys.stderr)
        bare_tps = None

    cfg = GPTConfig.gpt2_small()
    mfu = train_flops_per_token(cfg, S) * fw_tps / (peak_flops * n_dev)
    result = {
        "metric": "gpt2_small_train_tokens_per_sec_per_chip_via_JaxTrainer",
        "value": round(fw_tps / n_dev, 1),
        "unit": "tokens/s/chip",
        "vs_baseline": round(mfu / 0.40, 3),
    }
    if bare_tps is not None:
        result["bare_tokens_per_sec_per_chip"] = round(bare_tps / n_dev, 1)
        result["overhead_pct"] = round((bare_tps - fw_tps) / bare_tps * 100, 2)
    print(json.dumps(result))


if __name__ == "__main__":
    if "--bare" in sys.argv:
        tps, n = _timed_tokens_per_sec()
        print(json.dumps({"tokens_per_sec": tps, "n_devices": n}))
    else:
        main()
