"""Headline benchmark: GPT-2 small training throughput/MFU on the local TPU chip.

Prints ONE JSON line: {"metric", "value", "unit", "vs_baseline"}.
 - value: training tokens/sec/chip for GPT-2 small (124M), batch 16 x seq 1024.
 - vs_baseline: measured MFU / 0.40 — the BASELINE.json north star is >=40% MFU
   ("Ray Train data-parallel GPT-2 at >=40% MFU", the reference's parity
   standard transplanted to TPU); >1.0 beats the bar.

Timing note: through the axon relay, block_until_ready does not synchronize, so
we force a scalar fetch after a pipelined window of steps (fetch RTT ~75ms is
amortized over the window).
"""

from __future__ import annotations

import json
import sys
import time


def main() -> None:
    import jax

    import numpy as np

    from ray_tpu.models import (
        GPTConfig,
        create_train_state,
        default_optimizer,
        make_train_step,
        shard_batch,
        train_flops_per_token,
    )
    from ray_tpu.parallel import MeshSpec

    # v5e bf16 peak; override for other generations via env if needed.
    import os

    peak_flops = float(os.environ.get("RAY_TPU_PEAK_FLOPS", 197e12))

    B, S, warmup, iters = 16, 1024, 3, 20
    cfg = GPTConfig.gpt2_small()
    devices = jax.devices()
    mesh = MeshSpec(data=len(devices)).build(devices)
    opt = default_optimizer(learning_rate=3e-4)
    state = create_train_state(cfg, jax.random.PRNGKey(0), opt, mesh=mesh)
    step = make_train_step(cfg, opt, mesh=mesh)

    rng = np.random.default_rng(0)
    batch = shard_batch(
        {"tokens": rng.integers(0, cfg.vocab_size - 1, (B, S + 1)).astype(np.int32)},
        mesh,
    )
    for _ in range(warmup):
        state, m = step(state, batch)
    _ = float(m["loss"])  # sync

    t0 = time.time()
    for _ in range(iters):
        state, m = step(state, batch)
    _ = float(m["loss"])  # sync
    dt = (time.time() - t0) / iters

    tokens_per_sec = B * S / dt
    mfu = train_flops_per_token(cfg, S) * B * S / dt / (peak_flops * len(devices))
    result = {
        "metric": "gpt2_small_train_tokens_per_sec_per_chip",
        "value": round(tokens_per_sec / len(devices), 1),
        "unit": "tokens/s/chip",
        "vs_baseline": round(mfu / 0.40, 3),
    }
    print(json.dumps(result))


if __name__ == "__main__":
    main()
